#!/usr/bin/env python
"""Offline elastic re-stamp: adapt a verified checkpoint to a new
dp/pp/slice layout.

`python tools/elastic_resize.py CKPT_DIR [--dp M] [--pp K] [--slices S]
 [--step N] [--dry-run]`  (at least one of --dp / --pp / --slices)

The restore path (picotron_tpu/checkpoint.py) refuses to resume a
checkpoint into a mesh whose topology differs from the one it was saved
under — unless `checkpoint.elastic` is on, or the checkpoint has been
re-stamped by this tool. Re-stamping rewrites the step's meta.json for
the new layout (dp_size, plus micro_batch_size/gradient_accumulation_
steps re-factored at CONSTANT global batch — the token-exact cursor /
loss-parity invariant; and/or pp_size) and re-commits the manifest with
the new source topology, so the resumed run needs no special config: the
checkpoint simply IS a dp=M (pp=K) checkpoint afterwards. The Orbax
array data is not touched — global shapes are layout-independent and
Orbax reshards onto whatever mesh restores them.

A pp re-stamp is possible because checkpoints store the PP-PADDED global
layer stack (models/llama.pp_layer_placement pads to pp * ceil(L/pp)):
every pp whose split is even stores the SAME stack, so changing pp_size
is pure metadata. The tool verifies the slot layouts match BEFORE
touching anything; an uneven split (saved or target) bakes its pp into
the padded shape and is refused with the slot mismatch named. pp does
not enter global_batch_size (= mbs x ga x dp x ep), so a pure-pp
re-stamp leaves the batch plan untouched.

A slice re-stamp (`--slices S`, the slice-loss recovery path: a
multi-slice pod loses a slice and must come back at the surviving
hardware's shape) is pure placement metadata — the slice count never
enters an array sharding, it only partitions the mesh axes over DCN — so
it rides the same meta.json + manifest rewrite, usually alongside the
--dp/--pp change that shrinks the mesh onto the survivors. The target
count must still divide dp*pp at the TARGET sizes (the config-validation
rule), checked before anything is rewritten.

Safety: the step is deep-verified against its commit manifest BEFORE
anything is rewritten. Re-stamping rebuilds the manifest from the
current bytes, so running it on a corrupt store would bless the
corruption as "verified" — the tool hard-refuses instead. A legacy step
(pre-manifest lineage) gets its meta.json rewritten but NO manifest:
this tool never manufactures a verification claim the original commit
didn't make.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from picotron_tpu.ckpt_integrity.manifest import (  # noqa: E402
    atomic_write_text, build_manifest, verify_step_dir, write_manifest,
)
from picotron_tpu.resilience import elastic  # noqa: E402

STEP_RE = re.compile(r"^step_(\d{8})$")


def list_steps(save_dir: str) -> list[int]:
    try:
        names = os.listdir(save_dir)
    except FileNotFoundError:
        return []
    return sorted(int(m.group(1)) for n in names
                  if (m := STEP_RE.match(n)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="re-stamp a checkpoint step for a new dp/pp/slice "
                    "layout (constant global batch; even pp splits only)")
    ap.add_argument("save_dir", help="checkpoint directory (the trainer's "
                    "checkpoint.save_dir, containing step_XXXXXXXX dirs)")
    ap.add_argument("--dp", type=int, default=None,
                    help="target data-parallel size")
    ap.add_argument("--pp", type=int, default=None,
                    help="target pipeline-parallel size (the saved and "
                         "target padded layer stacks must match — even "
                         "splits only)")
    ap.add_argument("--slices", type=int, default=None,
                    help="target slice count (slice-loss recovery: "
                         "restart the surviving slices as a smaller "
                         "multi-slice or single-slice job; placement "
                         "metadata only — pair with --dp/--pp to shrink "
                         "the mesh onto the survivors)")
    ap.add_argument("--step", type=int, default=None,
                    help="step to re-stamp (default: newest step that "
                         "passes verification)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the plan without touching the store")
    args = ap.parse_args(argv)
    if args.dp is None and args.pp is None and args.slices is None:
        ap.error("pick a target topology: --dp M, --pp K and/or "
                 "--slices S")
    if args.pp is not None and args.pp < 1:
        ap.error(f"--pp must be >= 1, got {args.pp}")
    if args.slices is not None and args.slices < 1:
        ap.error(f"--slices must be >= 1, got {args.slices}")

    steps = list_steps(args.save_dir)
    if not steps:
        print(f"no checkpoint steps under {args.save_dir}",
              file=sys.stderr)
        return 1
    if args.step is not None:
        if args.step not in steps:
            print(f"step {args.step} not found under {args.save_dir}; "
                  f"available: {steps}", file=sys.stderr)
            return 1
        step = args.step
    else:
        step = next((s for s in reversed(steps)
                     if verify_step_dir(
                         os.path.join(args.save_dir,
                                      f"step_{s:08d}")).ok), None)
        if step is None:
            print(f"no step under {args.save_dir} passes verification",
                  file=sys.stderr)
            return 1
    step_dir = os.path.join(args.save_dir, f"step_{step:08d}")

    # Deep-verify BEFORE mutating: re-stamping rebuilds the manifest from
    # the bytes on disk, so a corrupt store would come out "verified" —
    # refuse rather than launder corruption into the lineage.
    res = verify_step_dir(step_dir, deep=True)
    if res.status == "corrupt":
        print(f"step {step} fails verification "
              f"({'; '.join(res.failures[:3])}); refusing to re-stamp a "
              f"corrupt checkpoint", file=sys.stderr)
        return 1

    with open(os.path.join(step_dir, "meta.json")) as f:
        meta = json.load(f)
    cfg = meta.get("config") or {}
    tr, dist = cfg.get("training") or {}, cfg.get("distributed") or {}
    if "micro_batch_size" not in tr or "dp_size" not in dist:
        print(f"step {step}'s meta.json records no training/distributed "
              f"config; cannot plan a constant-global-batch resize",
              file=sys.stderr)
        return 1

    saved = elastic.saved_topology(step_dir) or {}
    dp_new = args.dp if args.dp is not None else int(dist["dp_size"])
    try:
        # identity plan when --dp is absent: a pure-pp re-stamp leaves
        # the batch plan untouched (pp does not enter the global batch)
        plan = elastic.plan_resize(
            micro_batch_size=int(tr["micro_batch_size"]),
            gradient_accumulation_steps=int(
                tr["gradient_accumulation_steps"]),
            dp_size=int(dist["dp_size"]),
            dp_new=dp_new,
            ep_size=int(dist.get("ep_size", 1)))
    except ValueError as e:
        print(f"cannot resize step {step}: {e}", file=sys.stderr)
        return 1

    pp_old = int(saved.get("pp", dist.get("pp_size", 1)))
    pp_new = args.pp if args.pp is not None else pp_old
    if pp_new != pp_old:
        # The slot-layout gate, BEFORE anything is rewritten: only a pp
        # whose padded global layer stack matches the saved one (even
        # splits) can consume the stored arrays. Uneven splits bake their
        # pp into the padded shape — refuse with the mismatch named.
        from picotron_tpu.models.llama import pp_layer_placement

        layers = (cfg.get("model") or {}).get("num_hidden_layers")
        if not layers:
            print(f"step {step}'s meta.json records no "
                  f"model.num_hidden_layers; cannot verify the pp "
                  f"slot layout", file=sys.stderr)
            return 1
        src_padded, src_slots = pp_layer_placement(int(layers), pp_old)
        dst_padded, dst_slots = pp_layer_placement(int(layers), pp_new)
        if src_padded != dst_padded or list(src_slots) != list(dst_slots):
            print(f"cannot re-stamp step {step} to pp={pp_new}: the saved "
                  f"padded layer stack ({src_padded} slots at pp={pp_old}) "
                  f"and the target's ({dst_padded} slots at pp={pp_new}) "
                  f"place the {layers} real layers in different slots — "
                  f"only even splits share a stack; pick a pp that "
                  f"divides the padded layer count evenly",
                  file=sys.stderr)
            return 1

    slices_old = int(saved.get("slices", dist.get("slices", 1) or 1))
    slices_new = args.slices if args.slices is not None else slices_old
    if slices_new > 1:
        # the config-validation rule at the TARGET sizes: the slice
        # granule must be absorbable by dp*pp, or the resumed run would
        # refuse its own config before restoring anything
        if slices_new > plan.dp_new * pp_new or (
                plan.dp_new * pp_new) % slices_new != 0:
            print(f"cannot re-stamp step {step} to slices={slices_new}: "
                  f"slices must divide dp*pp = {plan.dp_new * pp_new} "
                  f"(dp={plan.dp_new}, pp={pp_new}) and not exceed it — "
                  f"the resumed run's config validation would refuse "
                  f"this layout", file=sys.stderr)
            return 1

    dl_state = meta.get("dataloader")
    if dl_state:
        # constant global batch -> pass-through; still validated so a
        # hand-edited store can't smuggle in a mid-batch cursor
        dl_state = elastic.translate_dataloader_state(
            dl_state, gbs_old=plan.global_batch_size,
            gbs_new=plan.global_batch_size)

    new_topo = {ax: int(saved.get(ax, dist.get(f"{ax}_size", 1)))
                for ax in elastic.TOPOLOGY_AXES}
    new_topo["dp"] = plan.dp_new
    new_topo["pp"] = pp_new
    new_topo["slices"] = slices_new
    new_topo["world_size"] = 1
    for ax in elastic.TOPOLOGY_AXES:
        new_topo["world_size"] *= new_topo[ax]

    print(f"step {step} under {args.save_dir} ({res.status}):")
    print(f"  topology  [{elastic.describe_topology(saved or None)}] -> "
          f"[{elastic.describe_topology(new_topo)}]")
    print(f"  batch     mbs {tr['micro_batch_size']} x ga "
          f"{tr['gradient_accumulation_steps']} x dp {dist['dp_size']} "
          f"-> mbs {plan.micro_batch_size} x ga "
          f"{plan.gradient_accumulation_steps} x dp {plan.dp_new} "
          f"(global batch {plan.global_batch_size}, unchanged)")
    if pp_new != pp_old:
        print(f"  pipeline  pp {pp_old} -> {pp_new} (same padded layer "
              f"stack — metadata only; stage programs rebuild from "
              f"config at startup)")
    if slices_new != slices_old:
        print(f"  slices    {slices_old} -> {slices_new} (placement "
              f"metadata only — no array touches a slice boundary)")
    if dl_state:
        print(f"  cursor    epoch {dl_state['epoch']}, sample "
              f"{dl_state['cursor']} (token-exact carry)")
    if args.dry_run:
        print("dry run: store not modified")
        return 0

    meta["config"]["distributed"]["dp_size"] = plan.dp_new
    meta["config"]["distributed"]["pp_size"] = pp_new
    meta["config"]["distributed"]["slices"] = slices_new
    meta["config"]["training"]["micro_batch_size"] = plan.micro_batch_size
    meta["config"]["training"]["gradient_accumulation_steps"] = \
        plan.gradient_accumulation_steps
    meta["elastic_restamp"] = {
        "from": saved or None, "to": new_topo,
        "tool": "tools/elastic_resize.py",
    }
    atomic_write_text(os.path.join(step_dir, "meta.json"),
                      json.dumps(meta, indent=1, sort_keys=True))

    if res.status == "verified":
        # meta.json's bytes changed, so the manifest must be re-committed
        # (it content-hashes every file) — with the new source topology.
        write_manifest(step_dir, build_manifest(step_dir, step=step,
                                                topology=new_topo))
        after = verify_step_dir(step_dir, deep=True)
        if after.status != "verified":
            print(f"re-stamp left step {step} unverified "
                  f"({'; '.join(after.failures[:3])})", file=sys.stderr)
            return 1
        print(f"  manifest  re-committed, step re-verified")
    else:
        print(f"  manifest  none (legacy step) — meta.json rewritten only")
    pp_hint = f" distributed.pp_size={pp_new}" if pp_new != pp_old else ""
    if slices_new != slices_old:
        pp_hint += f" distributed.slices={slices_new}"
    print(f"resume with distributed.dp_size={plan.dp_new}{pp_hint} "
          f"training.micro_batch_size={plan.micro_batch_size} "
          f"training.gradient_accumulation_steps="
          f"{plan.gradient_accumulation_steps} (checkpoint.elastic not "
          f"required: the store now records this topology)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
