#!/usr/bin/env python
"""Export a framework training checkpoint to an HF safetensors directory.

The round trip the reference never closes (its checkpoints are per-rank
.pth files locked to a topology, ref: checkpoint.py:242-260): train here,
export, then load anywhere `safetensors` does — HF `from_pretrained`
(weights), vLLM, or back into this framework via `--hf-dir`/`init_from_hf`.

  python tools/export_hf.py --config runs/exp/config.json \\
      --ckpt-dir ckpt --out ./exported_hf

Restores only the params subtree (no Adam moments — see tools/generate.py)
and writes the canonical HF Llama/Qwen2/Mixtral tensor names, biases and
tied heads included.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description="picotron-tpu -> HF export")
    ap.add_argument("--config", required=True,
                    help="training config JSON of the run")
    ap.add_argument("--ckpt-dir", required=True,
                    help="checkpoint save_dir of the run")
    ap.add_argument("--step", type=int, default=None,
                    help="checkpoint step (default: newest durable)")
    ap.add_argument("--out", required=True, help="output directory")
    args = ap.parse_args()

    import orbax.checkpoint as ocp

    from picotron_tpu.checkpoint import CheckpointManager, save_hf_safetensors
    from picotron_tpu.config import load_config
    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.models.llama import (
        init_params, pad_layers_for_pp, unpad_layers,
    )

    cfg = load_config(args.config)
    menv = MeshEnv.create(dp=1, devices=jax.devices()[:1])
    mgr = CheckpointManager(cfg, menv, directory=args.ckpt_dir)
    step_n = args.step if args.step is not None else mgr.latest_step()
    if step_n is None:
        ap.error(f"no checkpoints under {args.ckpt_dir}")

    nl, pp = cfg.model.num_hidden_layers, cfg.distributed.pp_size
    abstract = jax.eval_shape(
        lambda: pad_layers_for_pp(init_params(cfg.model, jax.random.key(0)),
                                  nl, pp))
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    restore_args = jax.tree.map(
        lambda x: ocp.ArrayRestoreArgs(dtype=x.dtype, sharding=sharding),
        abstract)
    with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ckptr:
        restored = ckptr.restore(
            f"{mgr.directory}/step_{step_n:08d}/state",
            args=ocp.args.PyTreeRestore(
                item={"params": abstract},
                restore_args={"params": restore_args},
                partial_restore=True))
    params = unpad_layers(restored["params"], nl, pp)
    save_hf_safetensors(params, args.out)
    print(f"exported step {step_n} -> {args.out}/model.safetensors")


if __name__ == "__main__":
    main()
