#!/usr/bin/env python
"""Export a framework training checkpoint to an HF safetensors directory.

The round trip the reference never closes (its checkpoints are per-rank
.pth files locked to a topology, ref: checkpoint.py:242-260): train here,
export, then load anywhere `safetensors` does — HF `from_pretrained`
(weights), vLLM, or back into this framework via `--hf-dir`/`init_from_hf`.

  python tools/export_hf.py --config runs/exp/config.json \\
      --ckpt-dir ckpt --out ./exported_hf

Restores only the params subtree (no Adam moments — see tools/generate.py)
and writes the canonical HF Llama/Qwen2/Mixtral tensor names, biases and
tied heads included.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description="picotron-tpu -> HF export")
    ap.add_argument("--config", required=True,
                    help="training config JSON of the run")
    ap.add_argument("--ckpt-dir", required=True,
                    help="checkpoint save_dir of the run")
    ap.add_argument("--step", type=int, default=None,
                    help="checkpoint step (default: newest durable)")
    ap.add_argument("--out", required=True, help="output directory")
    args = ap.parse_args()

    from picotron_tpu.checkpoint import restore_params_only, save_hf_safetensors
    from picotron_tpu.config import load_config

    cfg = load_config(args.config)
    params, step_n = restore_params_only(cfg, args.ckpt_dir, step=args.step)
    save_hf_safetensors(params, args.out)
    print(f"exported step {step_n} -> {args.out}/model.safetensors")


if __name__ == "__main__":
    main()
