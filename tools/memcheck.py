#!/usr/bin/env python
"""Will this config fit? — compile-only memory analysis, no chips needed.

Compiles the full train step for a config on an AOT/virtual mesh and prints
XLA's per-device memory breakdown (parameters + optimizer state, compiled
temporaries, argument/output buffers). Run it before burning pod time on a
layout that OOMs at step 1:

  python tools/memcheck.py --config runs/llama2-7b-dp4tp2pp2-1f1b/config.json
  python tools/memcheck.py --config cfg.json --sweep-mbs 1 2 4 8

The config's own device topology is simulated on host CPUs (same recipe as
the test suite), so a v5e-16 layout is analyzable on a laptop. Numbers are
XLA's CPU-backend estimates: layouts/padding differ slightly from TPU
compilation, but sizing decisions (does it fit in 16G with margin?) carry
over. The reference has no equivalent — you find out by OOM-ing the job
(its Slurm layer then greps the log, ref: base_job.slurm:82-94).

Compile time scales with model size: debug-size configs analyze in
seconds, multi-billion-parameter configs can take several minutes per mbs
point on the CPU backend — still far cheaper than a pod job that OOMs.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def analyze(cfg, mbs=None) -> dict:
    import jax

    from picotron_tpu.mesh import MeshEnv
    from picotron_tpu.parallel.api import init_sharded_state, make_train_step

    if mbs is not None:
        cfg = dataclasses.replace(
            cfg, training=dataclasses.replace(cfg.training,
                                              micro_batch_size=mbs))
    cfg.validate()
    menv = MeshEnv.from_config(cfg)
    # Abstract state + batch: nothing is materialized — a 7B config
    # analyzes without 7B of host RAM (init_sharded_state(abstract=True)).
    state = init_sharded_state(cfg, menv, jax.random.key(0), abstract=True)
    step = make_train_step(cfg, menv)
    t = cfg.training
    b = (t.micro_batch_size * cfg.distributed.dp_size
         * cfg.distributed.ep_size)
    import jax.numpy as jnp

    ids = jax.ShapeDtypeStruct(
        (t.gradient_accumulation_steps, b, t.seq_length), jnp.int32,
        sharding=menv.batch_sharding())
    stats = step.lower(state, (ids, ids)).compile().memory_analysis()
    gib = 1024 ** 3
    return {
        "micro_batch_size": t.micro_batch_size,
        "per_device_gib": {
            "arguments (params+moments+batch)":
                round(stats.argument_size_in_bytes / gib, 3),
            "temporaries": round(stats.temp_size_in_bytes / gib, 3),
            "outputs": round(stats.output_size_in_bytes / gib, 3),
            "total_estimate": round(
                (stats.argument_size_in_bytes + stats.temp_size_in_bytes)
                / gib, 3),
        },
    }


def _field_is_str(dotted: str) -> bool:
    """True when the dotted config path names a str (or Optional[str])
    dataclass field — the cases where a bare-string --override value is
    legitimate. Unknown paths return False (loud beats silent)."""
    import types
    import typing

    from picotron_tpu import config as cfg_mod

    cls = cfg_mod.Config
    parts = dotted.split(".")
    try:
        for p in parts[:-1]:
            cls = typing.get_type_hints(cls)[p]
        t = typing.get_type_hints(cls)[parts[-1]]
    except (KeyError, TypeError):
        return False
    if t is str:
        return True
    # both spellings of an optional/union string: typing.Optional[str]
    # (origin typing.Union) and PEP 604 `str | None` (origin
    # types.UnionType) — ADVICE r5
    return (typing.get_origin(t) in (typing.Union, types.UnionType)
            and str in typing.get_args(t))


def main() -> None:
    ap = argparse.ArgumentParser(description="picotron-tpu memory analysis")
    ap.add_argument("--config", required=True)
    ap.add_argument("--sweep-mbs", type=int, nargs="*", default=None,
                    help="analyze these micro-batch sizes instead of the "
                         "config's")
    ap.add_argument("--override", nargs="*", default=[], action="append",
                    metavar="SECTION.KEY=VALUE",
                    help="dotted config overrides applied before analysis "
                         "(e.g. distributed.zero1=true "
                         "distributed.sequence_parallel=true) — compare a "
                         "knob's memory effect without writing config "
                         "variants; repeated flags compose")
    args = ap.parse_args()
    # action=append + nargs=* gives a list per flag occurrence; flatten so
    # `--override a=1 --override b=2` composes instead of last-flag-wins
    # (argparse's bare nargs=* semantics silently dropped earlier flags —
    # a mis-measured config; code review r5)
    args.override = [ov for group in args.override for ov in group]

    from picotron_tpu.config import load_config
    from picotron_tpu.mesh import force_host_device_count

    if args.override:
        import tempfile

        with open(args.config) as f:
            raw = json.load(f)
        for ov in args.override:
            dotted, _, val = ov.partition("=")
            node = raw
            *path, key = dotted.split(".")
            for p in path:
                node = node.setdefault(p, {})
            try:
                node[key] = json.loads(val)  # true/false/numbers/lists
            except ValueError:
                # Bare strings stay strings for STRING-TYPED knobs:
                # `--override training.remat_policy=dots_attn` must not
                # demand shell-quoted embedded JSON quotes (ADVICE r4).
                # The knob's declared dataclass type decides — a typo'd
                # literal on a bool/number knob (`zero1=flase`) must stay
                # a loud error, not a truthy string that silently flips
                # the knob ON and measures the wrong config (code review
                # r5; checking the raw JSON's existing value instead
                # misses every key the config file omits as defaulted).
                if val in ("True", "False", "None"):
                    # Python-literal spellings stay loud even on string
                    # knobs: `run_name=None` means JSON null, not the
                    # string "None" (code review r5)
                    raise SystemExit(
                        f"--override {dotted}={val!r}: Python-literal "
                        f"spelling — use JSON (true/false/null "
                        f"lowercase, quotes for strings)")
                if not _field_is_str(dotted):
                    raise SystemExit(
                        f"--override {dotted}={val!r}: not valid JSON, "
                        f"and {dotted} is not a string-typed config "
                        f"field")
                node[key] = val
        tmp = tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False)
        json.dump(raw, tmp)
        tmp.close()
        args.config = tmp.name

    cfg = load_config(args.config)
    # Simulate the config's topology on host CPUs (backend-init-order
    # sensitive: must run before the first jax client exists).
    force_host_device_count(cfg.distributed.world_size)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    for mbs in (args.sweep_mbs or [None]):
        try:
            print(json.dumps(analyze(cfg, mbs)))
        except Exception as e:  # one OOM/compile failure must not end sweep
            print(json.dumps({"micro_batch_size": mbs,
                              "error": str(e)[:160]}))


if __name__ == "__main__":
    main()
